"""Collective flight recorder — the runtime half of graft-verify.

Static schedule verification (COLL002/COLL003) proves agreement where
the call graph is analyzable; everything else — data-dependent
schedules, third-party code, genuine races — needs runtime evidence.
Modeled on PyTorch's NCCL Flight Recorder: every eager collective in
multi-controller mode appends a :class:`CollectiveSignature`
(sequence number, op, shape/dtype, group, peer) to a fixed-size
per-rank ring buffer (``FLAGS comm_flight_recorder_len`` entries), so
that

- the **CommWatchdog's dump stage** prints the last-N ring entries of
  this rank alongside the stack dump (and, when a contract store is
  attached, a best-effort schedule diff against every peer that has
  published) — a real hang produces a *schedule diff*, not just
  stacks;
- the :func:`collective_contract` sanitizer (re-exported from
  ``paddle_tpu.analysis.sanitizers``) cross-checks the recorded
  schedules of all ranks through a shared KV store (TCPKVStore /
  FileKVStore) and raises :class:`CollectiveScheduleMismatch` naming
  BOTH ranks' last-N schedules when they diverge — the test-time proof
  that a reordered collective would have deadlocked.

Chaos site ``comm.reorder``: a ``drop`` fault here defers the current
collective's signature behind the NEXT one recorded on this rank —
the deterministic way for a test to manufacture exactly the swapped
schedule the static rules flag (see ``testing/chaos.py``).

Recording is cheap (a deque append under a lock) and stdlib-only; jax
never gets imported from here.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ...base import flags as _flags
from ...testing import chaos as _chaos
from ...utils.retries import Deadline

__all__ = [
    "CollectiveSignature",
    "FlightRecorder",
    "recorder",
    "record",
    "reset",
    "attach_contract",
    "register_dump_extra",
    "unregister_dump_extra",
    "contract",
    "schedule_diff",
    "dump_on_watchdog",
]

# ops whose signatures are legitimately rank-divergent (the two
# endpoints of a transfer record mirrored entries) — the cross-rank
# contract skips them; COLL003 owns their static pairing. The disagg
# KV-handoff legs (inference/disagg.py) are the cross-ROLE analogue:
# the prefill side records handoff_send where the decode side records
# handoff_recv, so a hang dump can name both roles' schedules without
# the contract calling the asymmetry a divergence. ``train_step`` is
# the training supervisor's per-step telemetry beacon
# (training/telemetry.py): its detail carries per-rank step times and
# gradient fingerprints — divergent by nature, but exactly what a hang
# dump should print (the last steps each rank completed, and how slow).
_RANK_DIVERGENT_OPS = ("send", "recv", "handoff_send", "handoff_recv",
                       "train_step")


@dataclass(frozen=True)
class CollectiveSignature:
    seq: int            # per-rank issue counter (1-based)
    op: str             # all_reduce[sum] / all_gather / broadcast / ...
    shape: Tuple[int, ...]
    dtype: str
    group: str          # group/axis the op runs over
    peer: Optional[int] = None   # p2p endpoint / broadcast src
    detail: str = ""    # op params every rank must agree on (src, perm)
    t: float = 0.0      # host wall clock at issue time

    def key(self) -> Tuple:
        """The rank-invariant part: what every rank must agree on."""
        return (self.op, self.shape, self.dtype, self.group, self.detail)

    def format(self) -> str:
        s = f"#{self.seq} {self.op} {self.dtype}{list(self.shape)} " \
            f"group={self.group}"
        if self.peer is not None:
            s += f" peer={self.peer}"
        if self.detail:
            s += f" {self.detail}"
        return s

    def to_json(self) -> Dict:
        return {"seq": self.seq, "op": self.op,
                "shape": list(self.shape), "dtype": self.dtype,
                "group": self.group, "peer": self.peer,
                "detail": self.detail, "t": self.t}

    @classmethod
    def from_json(cls, d: Dict) -> "CollectiveSignature":
        return cls(seq=int(d["seq"]), op=d["op"],
                   shape=tuple(d["shape"]), dtype=d["dtype"],
                   group=d["group"], peer=d.get("peer"),
                   detail=d.get("detail", ""), t=float(d.get("t", 0.0)))


class FlightRecorder:
    """Fixed-size ring of the collectives this rank issued, in issue
    order. Signatures are appended BEFORE the collective executes, so
    a hang still shows the op the rank is stuck in."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(_flags.flag("comm_flight_recorder_len"))
        self.capacity = max(1, capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._pending: List[Tuple] = []  # comm.reorder deferral FIFO
        self._contract_round = 0
        self._lock = threading.Lock()

    def record(self, op: str, shape: Tuple[int, ...] = (),
               dtype: str = "", group: str = "world",
               peer: Optional[int] = None, detail: str = "") -> None:
        entry = (op, tuple(int(d) for d in shape), str(dtype),
                 str(group), peer, detail)
        # chaos site comm.reorder: a drop DEFERS this signature until
        # the next NON-deferred collective on this rank (FIFO, so
        # consecutive drops each take effect instead of silently
        # cancelling) — the injected schedule swap the contract and
        # COLL002 must catch
        deferred = not _chaos.inject("comm.reorder")
        with self._lock:
            if deferred:
                self._pending.append(entry)
                return
            self._append(entry)
            self._flush_pending_locked()

    def _append(self, entry: Tuple) -> None:
        op, shape, dtype, group, peer, detail = entry
        self._seq += 1
        self._ring.append(CollectiveSignature(
            seq=self._seq, op=op, shape=shape, dtype=dtype,
            group=group, peer=peer, detail=detail, t=time.time()))

    def _flush_pending_locked(self) -> None:
        while self._pending:
            self._append(self._pending.pop(0))

    def snapshot(self, last_n: Optional[int] = None
                 ) -> List[CollectiveSignature]:
        """The last-N recorded signatures (deferred entries flushed
        first — a snapshot is a synchronization point)."""
        with self._lock:
            self._flush_pending_locked()
            entries = list(self._ring)
        if last_n is not None:
            entries = entries[-last_n:]
        return entries

    def next_contract_round(self) -> int:
        with self._lock:
            self._contract_round += 1
            return self._contract_round

    def dump(self, file, last_n: Optional[int] = None,
             header: str = "CollectiveFlightRecorder") -> None:
        entries = self.snapshot(last_n)
        file.write(f"{header}: last {len(entries)} collective(s) "
                   "issued by this rank (most recent last):\n")
        if not entries:
            file.write("  (no collectives recorded)\n")
        for sig in entries:
            file.write(f"  {sig.format()}\n")


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()
# (store, rank, world_size) when a contract has been attached — lets
# the watchdog publish/fetch schedules while the process still can
_contract_binding: Optional[Tuple] = None
# extra sections appended to the watchdog dump: fn(file) callables
# registered by subsystems with hang-relevant evidence of their own
# (training.telemetry names persistent stragglers here, so a hang dump
# answers "WHO is slow", not just "we are hung")
_dump_extras: List = []


def register_dump_extra(fn) -> None:
    """Append ``fn(file)`` to the watchdog dump. Re-registering the
    same callable is a no-op; :func:`unregister_dump_extra` removes one
    (retired subsystem instances must not keep writing stale evidence
    into dumps — or be retained forever); :func:`reset` clears all."""
    if fn not in _dump_extras:
        _dump_extras.append(fn)


def unregister_dump_extra(fn) -> None:
    try:
        _dump_extras.remove(fn)
    except ValueError:
        pass


def recorder() -> FlightRecorder:
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def record(op: str, shape: Tuple[int, ...] = (), dtype: str = "",
           group: str = "world", peer: Optional[int] = None,
           detail: str = "") -> None:
    """Module-level sugar used by the instrumented collective sites."""
    recorder().record(op, shape, dtype, group, peer, detail)


def reset() -> None:
    """Drop the recorder, contract binding and dump extras (tests)."""
    global _recorder, _contract_binding
    with _recorder_lock:
        _recorder = None
        _contract_binding = None
        del _dump_extras[:]


def attach_contract(store, rank: int, world_size: int) -> None:
    """Register the KV store the watchdog may use to publish/fetch
    schedules at dump time. :func:`contract` attaches automatically."""
    global _contract_binding
    _contract_binding = (store, int(rank), int(world_size))


# ---------------------------------------------------------------------------
# Cross-rank schedule comparison


def schedule_diff(schedules: Dict[int, List[CollectiveSignature]]
                  ) -> Optional[str]:
    """Human-readable divergence report across per-rank schedules, or
    None when every rank agrees. Point-to-point entries (send/recv)
    are skipped — their signatures are rank-divergent by design. The
    compare is positional from the start of each (filtered) list;
    hang-dump diffs taken from WRAPPED rings with asymmetric p2p
    volume may therefore misalign — every printed entry carries its
    per-rank ``#seq`` so the reader can re-align by hand (the
    contract path pre-filters before trimming and is immune)."""
    comparable = {
        r: [s for s in sched if s.op not in _RANK_DIVERGENT_OPS]
        for r, sched in schedules.items()
    }
    if len(comparable) < 2:
        return None
    ref_rank = min(comparable)
    ref = comparable[ref_rank]
    divergences = []
    for r in sorted(comparable):
        if r == ref_rank:
            continue
        other = comparable[r]
        pos = None
        for i, (a, b) in enumerate(zip(ref, other)):
            if a.key() != b.key():
                pos = i
                break
        if pos is None and len(ref) != len(other):
            pos = min(len(ref), len(other))
        if pos is not None:
            a = ref[pos].format() if pos < len(ref) else "(nothing)"
            b = other[pos].format() if pos < len(other) else "(nothing)"
            divergences.append(
                f"rank {ref_rank} and rank {r} diverge at schedule "
                f"position {pos}:\n"
                f"  rank {ref_rank}: {a}\n"
                f"  rank {r}: {b}")
    if not divergences:
        return None
    lines = divergences
    lines.append("full recorded schedules:")
    for r in sorted(schedules):
        lines.append(f"  rank {r}:")
        entries = schedules[r]
        if not entries:
            lines.append("    (no collectives recorded)")
        for sig in entries:
            lines.append(f"    {sig.format()}")
    return "\n".join(lines)


def contract(store, rank: int, world_size: int, *, last_n: int = 32,
             deadline=None, recorder_: Optional[FlightRecorder] = None,
             tag: str = "default") -> Dict[int, List[CollectiveSignature]]:
    """Cross-check this rank's recorded schedule against every peer
    through ``store`` (any ``distributed.store.KVStore``). Publishes
    the local last-N schedule, waits (under ``deadline``, default 30 s)
    for all peers' rounds, and raises
    ``analysis.sanitizers.CollectiveScheduleMismatch`` — naming every
    rank's schedule — on divergence. Every rank must call this the
    same number of times (the contract is itself a collective), and
    round ids count per INCARNATION: after a rank relaunch, pass a
    fresh ``tag=`` (or a fresh store) so the new incarnation's round 1
    doesn't read a key a previous incarnation published. Returns the
    per-rank schedules on agreement."""
    from ...analysis.sanitizers import CollectiveScheduleMismatch

    rec = recorder_ if recorder_ is not None else recorder()
    attach_contract(store, rank, world_size)
    round_id = rec.next_contract_round()
    # filter rank-divergent entries BEFORE trimming: asymmetric (but
    # legal) p2p activity must not shift the comparison windows of
    # different ranks against each other
    mine = [s for s in rec.snapshot()
            if s.op not in _RANK_DIVERGENT_OPS][-last_n:]
    store.set(f"graft/fr/{tag}/{round_id}/{rank}",
              json.dumps([s.to_json() for s in mine]))
    dl = Deadline.coerce(deadline) if deadline is not None \
        else Deadline(30.0)
    schedules: Dict[int, List[CollectiveSignature]] = {rank: mine}
    for r in range(world_size):
        if r == rank:
            continue
        key = f"graft/fr/{tag}/{round_id}/{r}"
        while True:
            raw = store.get(key)
            if raw:
                schedules[r] = [CollectiveSignature.from_json(d)
                                for d in json.loads(raw)]
                break
            dl.check(f"collective_contract: waiting for rank {r}'s "
                     f"schedule (round {round_id})")
            time.sleep(0.05)
    diff = schedule_diff(schedules)
    if diff is not None:
        raise CollectiveScheduleMismatch(
            "collective_contract: cross-rank collective schedule "
            f"divergence (round {round_id}, last {last_n}):\n{diff}")
    return schedules


# grace the hang-dump worker gets for a FAST store before the dump
# stage returns; a slower exchange keeps running detached and prints
# its diff whenever the store answers (the watchdog's monitor thread —
# the abort safety net and every other wait's ladder — never blocks
# longer than this)
_HANG_DUMP_GRACE_S = 0.5
# a peer schedule published longer ago than this is labeled stale — it
# likely belongs to a previous incident (the store outlives aborted
# incarnations and fr_hang keys are never deleted)
_HANG_DUMP_STALE_S = 300.0


def _hang_dump_exchange(store, rank: int, world_size: int,
                        mine: List[CollectiveSignature], file):
    """Publish this rank's schedule, fetch peers', and WRITE the diff
    section — runs entirely on a scrap daemon thread so a slow/dead
    store never stalls the watchdog's monitor thread (a late diff
    simply prints when the store finally answers; if the abort stage
    kills the process first, the diff was unobtainable in time
    anyway)."""
    try:
        store.set(f"graft/fr_hang/{rank}", json.dumps({
            "published_at": time.time(),
            "schedule": [s.to_json() for s in mine]}))
        schedules = {rank: mine}
        stale = []
        for r in range(world_size):
            if r == rank:
                continue
            raw = store.get(f"graft/fr_hang/{r}")
            if not raw:
                continue
            data = json.loads(raw)
            if isinstance(data, dict):
                age = time.time() - float(data.get("published_at", 0.0))
                entries = data.get("schedule", [])
            else:  # bare-list publishers (age unknown)
                age, entries = float("inf"), data
            schedules[r] = [CollectiveSignature.from_json(d)
                            for d in entries]
            if age > _HANG_DUMP_STALE_S:
                stale.append(r)
        out = [
            f"CollectiveFlightRecorder: hang-dump schedules published "
            f"by ranks {sorted(schedules)} (of {world_size})"
        ]
        if stale:
            out.append(
                f"WARNING: rank(s) {stale} published their schedules "
                f"over {_HANG_DUMP_STALE_S:.0f}s ago — possibly a "
                "PREVIOUS incident's dump; treat their diff lines "
                "with suspicion")
        diff = schedule_diff(schedules)
        if diff is not None:
            out.append("cross-rank schedule diff:\n" + diff)
        elif len(schedules) > 1:
            out.append(
                "published schedules agree — the hang is not a "
                "schedule divergence among the ranks above")
            # still print WHAT each rank issued: for a cross-role hang
            # (disagg handoff legs are rank-divergent and excluded from
            # the diff) the peer's last ops are the evidence — e.g. a
            # decode worker stuck because the prefill role stopped
            # sending shows exactly where the sender's schedule ends
            out.append("published schedules:")
            for r in sorted(schedules):
                out.append(f"  rank {r}:")
                entries = schedules[r]
                if not entries:
                    out.append("    (no collectives recorded)")
                for sig in entries:
                    out.append(f"    {sig.format()}")
        file.write("\n".join(out) + "\n")
    except Exception as e:  # noqa: BLE001 — diagnostics must not raise
        try:
            file.write(f"CollectiveFlightRecorder: peer schedule "
                       f"exchange failed "
                       f"({type(e).__name__}: {e})\n")
        except Exception:  # noqa: BLE001
            pass


def dump_on_watchdog(file) -> None:
    """Called by the CommWatchdog's stack-dump stage: print this
    rank's ring synchronously; with a contract store attached, kick
    off the publish + peer schedule diff on a daemon thread (waiting
    at most ``_HANG_DUMP_GRACE_S`` so a healthy store prints inline)
    — a real cross-rank hang yields a schedule diff while both
    processes are still alive to produce one, and a dead store cannot
    delay the watchdog's abort ladder. Peer schedules older than
    ``_HANG_DUMP_STALE_S`` are labeled as likely belonging to a
    previous incident."""
    rec = recorder()
    rec.dump(file, header="CollectiveFlightRecorder (watchdog dump)")
    for extra in list(_dump_extras):
        try:
            extra(file)
        except Exception as e:  # noqa: BLE001 — diagnostics must not raise
            try:
                file.write(f"CollectiveFlightRecorder: dump extra "
                           f"{getattr(extra, '__qualname__', extra)!r} "
                           f"failed ({type(e).__name__}: {e})\n")
            except Exception:  # noqa: BLE001
                pass
    binding = _contract_binding
    if binding is None:
        return
    store, rank, world_size = binding
    worker = threading.Thread(
        target=_hang_dump_exchange,
        args=(store, rank, world_size, rec.snapshot(), file),
        daemon=True)
    worker.start()
    worker.join(_HANG_DUMP_GRACE_S)
    if worker.is_alive():
        file.write(
            "CollectiveFlightRecorder: peer schedule exchange still "
            "in flight (slow store?) — the diff will print when it "
            "lands; not delaying the watchdog ladder\n")
