"""Collective watchdog.

Redesign of the reference's comm-task watchdog (ref:
paddle/fluid/distributed/collective/process_group_nccl.cc NCCL watchdog
thread; common/flags.cc FLAGS_pg_timeout): there, a daemon polls each
enqueued NCCL kernel's state and tears the process down when one exceeds
the process-group timeout, so the launcher can relaunch.

On TPU, XLA owns kernel scheduling and there is no per-kernel host
handle to poll — a stuck collective surfaces as a *blocking host wait on
device results*: a barrier, a device synchronize, or fetching a jit
step's outputs while a peer host is dead (multi-host programs stall in
dispatch until every process arrives). The watchdog therefore monitors
host-side waits:

- every monitored wait runs under :func:`watch`, which registers
  ``(description, start_time)`` in a table;
- a daemon thread wakes every few seconds; any wait older than
  ``FLAGS comm_timeout_s`` triggers a report — all-thread stack dump
  (the analogue of the reference dumping its comm trace buffer) — and,
  if ``FLAGS comm_abort_on_timeout`` is set, ``os._exit(124)`` so the
  launcher / elastic manager relaunches the job (the reference's
  async-error-handling teardown path).

``paddle_tpu.distributed.barrier`` and ``paddle_tpu.device.synchronize``
run their blocking waits under :func:`watch`.
"""
from __future__ import annotations

import faulthandler
import itertools
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional, Tuple

from ...base import flags as _flags

_EXIT_CODE = 124  # conventional timeout exit; elastic treats any death as a scale event


class CommWatchdog:
    """Singleton daemon watching registered host-side collective waits."""

    _instance: Optional["CommWatchdog"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._waits: Dict[int, Tuple[str, float]] = {}
        self._ids = itertools.count()
        self._mu = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._kick = threading.Event()  # wakes the daemon on new registrations
        self._reported: set = set()
        # test seam: replaces the dump+abort action
        self._on_timeout: Optional[Callable[[str, float], None]] = None

    @classmethod
    def instance(cls) -> "CommWatchdog":
        with cls._lock:
            if cls._instance is None:
                cls._instance = CommWatchdog()
            return cls._instance

    # -- registration --------------------------------------------------
    @contextmanager
    def watch(self, desc: str):
        """Run a blocking wait under watchdog supervision."""
        wid = next(self._ids)
        with self._mu:
            self._waits[wid] = (desc, time.monotonic())
        self._ensure_thread()
        self._kick.set()  # re-evaluate the poll interval for this wait
        try:
            yield
        finally:
            with self._mu:
                self._waits.pop(wid, None)
                self._reported.discard(wid)

    # -- daemon --------------------------------------------------------
    def _ensure_thread(self):
        with self._mu:  # two first-waiters racing here must not fork two daemons
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="paddle_tpu_comm_watchdog", daemon=True
                )
                self._thread.start()

    def _poll_interval(self) -> float:
        timeout = float(_flags.flag("comm_timeout_s"))
        return max(0.05, min(5.0, timeout / 4.0))

    def _run(self):
        while not self._stop.is_set():
            self._kick.wait(self._poll_interval())
            self._kick.clear()
            if self._stop.is_set():
                break
            timeout = float(_flags.flag("comm_timeout_s"))
            now = time.monotonic()
            with self._mu:
                expired = [
                    (wid, desc, now - start)
                    for wid, (desc, start) in self._waits.items()
                    if now - start > timeout and wid not in self._reported
                ]
                for wid, _, _ in expired:
                    self._reported.add(wid)
            for _, desc, age in expired:
                self._fire(desc, age)

    def _fire(self, desc: str, age: float):
        if self._on_timeout is not None:
            self._on_timeout(desc, age)
            return
        from ...utils import log as _log

        msg = (
            f"CommWatchdog: wait '{desc}' exceeded comm_timeout_s "
            f"({age:.1f}s); a peer host is likely dead or the device hung."
        )
        _log.warning(msg)
        sys.stderr.write(msg + "\n")
        faulthandler.dump_traceback(all_threads=True, file=sys.stderr)
        if bool(_flags.flag("comm_abort_on_timeout")):
            sys.stderr.write(
                f"CommWatchdog: aborting (exit {_EXIT_CODE}) for relaunch\n"
            )
            sys.stderr.flush()
            os._exit(_EXIT_CODE)

    def stop(self):
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None


def watch(desc: str):
    """Context manager: supervise a blocking wait (module-level sugar)."""
    return CommWatchdog.instance().watch(desc)
