"""Collective watchdog.

Redesign of the reference's comm-task watchdog (ref:
paddle/fluid/distributed/collective/process_group_nccl.cc NCCL watchdog
thread; common/flags.cc FLAGS_pg_timeout): there, a daemon polls each
enqueued NCCL kernel's state and tears the process down when one exceeds
the process-group timeout, so the launcher can relaunch.

On TPU, XLA owns kernel scheduling and there is no per-kernel host
handle to poll — a stuck collective surfaces as a *blocking host wait on
device results*: a barrier, a device synchronize, or fetching a jit
step's outputs while a peer host is dead (multi-host programs stall in
dispatch until every process arrives). The watchdog therefore monitors
host-side waits:

- every monitored wait runs under :func:`watch`, which registers a
  ``Deadline`` (paddle_tpu.utils.retries) of ``FLAGS comm_timeout_s``;
- a daemon thread polls and escalates each wait up an ACTION LADDER at
  fractions of its deadline (instead of one do-everything timeout):

  1. **warn** at ``FLAGS comm_warn_fraction`` (default 0.5) — a log
     line naming the wait, so a slow-but-alive peer shows up in logs
     long before teardown;
  2. **dump** at ``FLAGS comm_dump_fraction`` (default 0.75) — an
     all-thread stack dump (the analogue of the reference dumping its
     comm trace buffer) while the process is still alive to dump it;
  3. **abort** at 1.0 — if ``FLAGS comm_abort_on_timeout`` is set,
     ``os._exit(124)`` so the launcher / elastic manager relaunches the
     job (the reference's async-error-handling teardown path).

``paddle_tpu.distributed.barrier`` and ``paddle_tpu.device.synchronize``
run their blocking waits under :func:`watch`.
"""
from __future__ import annotations

import faulthandler
import itertools
import os
import sys
import threading
from contextlib import contextmanager
from typing import Callable, Dict, Optional, Tuple

from ...base import flags as _flags
from ...utils.retries import Deadline

_EXIT_CODE = 124  # conventional timeout exit; elastic treats any death as a scale event

# ladder stages in escalation order: (name, fraction-flag); abort always
# fires at the full deadline
_STAGES = (("warn", "comm_warn_fraction"), ("dump", "comm_dump_fraction"),
           ("abort", None))


class CommWatchdog:
    """Singleton daemon watching registered host-side collective waits."""

    _instance: Optional["CommWatchdog"] = None
    _lock = threading.Lock()

    def __init__(self):
        # wid -> (description, Deadline); the Deadline is fixed at
        # watch() entry so a mid-wait flag change cannot un-expire it
        self._waits: Dict[int, Tuple[str, Deadline]] = {}
        self._ids = itertools.count()
        self._mu = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._kick = threading.Event()  # wakes the daemon on new registrations
        self._stage_reached: Dict[int, int] = {}  # wid -> ladder index
        # test seams: _on_timeout replaces the dump+abort actions (abort
        # stage routes to it); _on_stage observes/replaces EVERY stage
        self._on_timeout: Optional[Callable[[str, float], None]] = None
        self._on_stage: Optional[Callable[[str, str, float], None]] = None

    @classmethod
    def instance(cls) -> "CommWatchdog":
        with cls._lock:
            if cls._instance is None:
                cls._instance = CommWatchdog()
            return cls._instance

    # -- registration --------------------------------------------------
    @contextmanager
    def watch(self, desc: str, deadline: Optional[Deadline] = None):
        """Run a blocking wait under watchdog supervision. The wait's
        budget is ``deadline`` (when the caller already has one) or a
        fresh Deadline of ``FLAGS comm_timeout_s``."""
        wid = next(self._ids)
        dl = deadline if deadline is not None else Deadline(
            float(_flags.flag("comm_timeout_s")))
        with self._mu:
            self._waits[wid] = (desc, dl)
        self._ensure_thread()
        self._kick.set()  # re-evaluate the poll interval for this wait
        try:
            yield dl
        finally:
            with self._mu:
                self._waits.pop(wid, None)
                self._stage_reached.pop(wid, None)

    # -- daemon --------------------------------------------------------
    def _ensure_thread(self):
        with self._mu:  # two first-waiters racing here must not fork two daemons
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="paddle_tpu_comm_watchdog", daemon=True
                )
                self._thread.start()

    def _fractions(self):
        # clamp to [0, 1]: a fraction flag set past 1.0 must not gate
        # the ABORT stage behind an unreachable threshold (the ladder
        # escalates in order, so an unreachable early stage would
        # silently disable the relaunch safety net)
        fr = []
        for _name, flag in _STAGES:
            fr.append(1.0 if flag is None
                      else min(max(float(_flags.flag(flag)), 0.0), 1.0))
        return fr

    def _poll_interval(self) -> float:
        # resolve the smallest gap between ladder stages, not just the
        # final deadline (warn at 0.5x needs finer polling than x/4),
        # against the SHORTEST registered budget — a caller-supplied
        # 0.2s Deadline under an hours-long flag still gets fine polls
        timeout = float(_flags.flag("comm_timeout_s"))
        with self._mu:
            budgets = [dl.budget for _, dl in self._waits.values()
                       if dl.budget is not None]
        ref = min(budgets + [timeout])
        fracs = sorted(set(self._fractions()))
        gap = min([fracs[0]] + [b - a for a, b in zip(fracs, fracs[1:])])
        return max(0.02, min(5.0, ref * max(gap, 0.125) / 2.0))

    def _run(self):
        while not self._stop.is_set():
            self._kick.wait(self._poll_interval())
            self._kick.clear()
            if self._stop.is_set():
                break
            fracs = self._fractions()
            fired = []
            with self._mu:
                for wid, (desc, dl) in self._waits.items():
                    consumed = dl.fraction_consumed()
                    reached = self._stage_reached.get(wid, 0)
                    # escalate through every stage the wait has crossed
                    # (a long poll gap must not skip the dump)
                    while (reached < len(_STAGES)
                           and consumed >= fracs[reached]):
                        fired.append((_STAGES[reached][0], desc,
                                      dl.elapsed()))
                        reached += 1
                        self._stage_reached[wid] = reached
            for stage, desc, age in fired:
                self._fire(stage, desc, age)

    def _fire(self, stage: str, desc: str, age: float):
        if self._on_stage is not None:
            self._on_stage(stage, desc, age)
            return
        if stage == "abort" and self._on_timeout is not None:
            self._on_timeout(desc, age)
            return
        from ...utils import log as _log

        if stage == "warn":
            if self._on_timeout is not None:
                return  # the seam replaces ALL real actions, warn included
            msg = (
                f"CommWatchdog: wait '{desc}' has consumed "
                f"{float(_flags.flag('comm_warn_fraction')):.0%} of its "
                f"deadline ({age:.1f}s); a peer host may be slow or dead."
            )
            _log.warning(msg)
            sys.stderr.write(msg + "\n")
        elif stage == "dump":
            if self._on_timeout is not None:
                return  # seam replaces the dump+abort actions
            msg = (
                f"CommWatchdog: wait '{desc}' at "
                f"{float(_flags.flag('comm_dump_fraction')):.0%} of its "
                f"deadline ({age:.1f}s) — dumping all-thread stacks."
            )
            _log.warning(msg)
            sys.stderr.write(msg + "\n")
            faulthandler.dump_traceback(all_threads=True, file=sys.stderr)
            # the analogue of the reference dumping its comm trace
            # buffer: the last-N collective signatures this rank issued
            # (and, when a contract store is attached, a schedule diff
            # against every peer that published) — a real cross-rank
            # hang yields a SCHEDULE DIFF, not just stacks
            try:
                from .flight_recorder import dump_on_watchdog

                dump_on_watchdog(sys.stderr)
            except Exception:  # noqa: BLE001 — diagnostics must not raise
                pass
        elif stage == "abort":
            msg = (
                f"CommWatchdog: wait '{desc}' exceeded its deadline "
                f"({age:.1f}s); a peer host is likely dead or the device "
                "hung."
            )
            _log.warning(msg)
            sys.stderr.write(msg + "\n")
            if bool(_flags.flag("comm_abort_on_timeout")):
                sys.stderr.write(
                    f"CommWatchdog: aborting (exit {_EXIT_CODE}) for relaunch\n"
                )
                sys.stderr.flush()
                os._exit(_EXIT_CODE)

    def stop(self):
        self._stop.set()
        self._kick.set()
        # `_thread` is guarded by `_mu` (see _ensure_thread): take the
        # handoff under the lock so a stop() racing a watch() cannot
        # observe a half-installed daemon — but join OUTSIDE it, since
        # the daemon itself takes `_mu` in _poll_interval and would
        # stall the join until its timeout
        with self._mu:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=1.0)


def watch(desc: str, deadline: Optional[Deadline] = None):
    """Context manager: supervise a blocking wait (module-level sugar)."""
    return CommWatchdog.instance().watch(desc, deadline)
