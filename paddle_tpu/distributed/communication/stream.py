"""paddle.distributed.communication.stream — stream-variant collectives.

ref: python/paddle/distributed/communication/stream/__init__.py (11
names). The reference's stream API chooses which CUDA stream a NCCL
collective runs on (``use_calc_stream=True`` skips the comm-stream
hop). XLA has no user-visible streams: collectives are scheduled by the
compiler inside the program, so every stream variant IS the plain
collective — the extra ``use_calc_stream`` knob is accepted and
ignored (always-true semantics), and each call returns the plain
call's result (sync semantics; XLA dispatch is already async at the
runtime level)."""
from __future__ import annotations

import functools

from . import (
    all_gather as _all_gather,
    all_reduce as _all_reduce,
    alltoall as _alltoall,
    alltoall_single as _alltoall_single,
    broadcast as _broadcast,
    gather as _gather,
    recv as _recv,
    reduce as _reduce,
    reduce_scatter as _reduce_scatter,
    scatter as _scatter,
    send as _send,
)

__all__ = [
    "all_gather", "all_reduce", "alltoall", "alltoall_single", "broadcast",
    "reduce", "reduce_scatter", "recv", "scatter", "send", "gather",
]


def _stream_variant(fn):
    @functools.wraps(fn)
    def wrapped(*args, use_calc_stream: bool = False, **kwargs):
        return fn(*args, **kwargs)

    wrapped.__doc__ = (
        f"stream.{fn.__name__} (ref: communication/stream/"
        f"{fn.__name__}.py) — see module docstring: on XLA the stream "
        "choice collapses into the compiled schedule; delegates to "
        f"distributed.{fn.__name__}."
    )
    return wrapped


all_gather = _stream_variant(_all_gather)
all_reduce = _stream_variant(_all_reduce)
alltoall = _stream_variant(_alltoall)
alltoall_single = _stream_variant(_alltoall_single)
broadcast = _stream_variant(_broadcast)
reduce = _stream_variant(_reduce)
reduce_scatter = _stream_variant(_reduce_scatter)
recv = _stream_variant(_recv)
scatter = _stream_variant(_scatter)
send = _stream_variant(_send)
gather = _stream_variant(_gather)
