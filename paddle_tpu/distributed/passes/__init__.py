"""paddle.distributed.passes — program-transform passes.

ref: python/paddle/distributed/passes/__init__.py (new_pass /
PassManager / PassContext; pass_base.py:20,131,350). The reference's
passes rewrite a static Program's op graph (fuse_gemm_epilogue,
auto_parallel_recompute, fuse_optimizer, …). Here the "program" is a
traced jax function: XLA already performs the fusion passes during
compilation, so the pass framework transforms CALLABLES — a pass takes
the step function and returns a wrapped one. Registered passes:

- ``auto_parallel_recompute``: wraps the function in ``jax.checkpoint``
  (the reference pass inserts recompute subgraphs).
- ``auto_parallel_amp`` / ``auto_parallel_fp16``: runs the function
  under ``amp.auto_cast`` O1/O2.
- ``fuse_gemm_epilogue`` / ``fused_attention`` / ``fuse_optimizer`` /
  ``fuse_all_reduce``: identity passes — the XLA compiler performs
  these rewrites unconditionally; registering them keeps pass lists
  portable from the reference.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

__all__ = ["new_pass", "PassManager", "PassContext"]


class PassContext:
    """ref: pass_base.py:20."""

    def __init__(self):
        self._applied_passes = []
        self._attrs = {}

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class PassBase:
    """A pass transforms a step callable (ref: pass_base.py PassBase —
    _apply_single_impl over Programs becomes apply() over callables)."""

    _REGISTERED_PASSES: Dict[str, type] = {}

    name = "base"

    def __init__(self):
        self._attrs: Dict[str, object] = {}

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    @classmethod
    def register(cls, pass_cls):
        cls._REGISTERED_PASSES[pass_cls.name] = pass_cls
        return pass_cls

    def apply(self, fn: Callable, context: Optional[PassContext] = None):
        out = self._apply_impl(fn)
        if context is not None:
            context._applied_passes.append(self)
        return out

    def _apply_impl(self, fn):
        raise NotImplementedError


@PassBase.register
class _RecomputePass(PassBase):
    name = "auto_parallel_recompute"

    def _apply_impl(self, fn):
        import jax

        policy = self.get_attr("checkpoint_policy")
        kwargs = {"policy": policy} if policy is not None else {}
        return jax.checkpoint(fn, **kwargs)


class _AmpPass(PassBase):
    name = "auto_parallel_amp"
    level = "O1"

    def _apply_impl(self, fn):
        import functools

        from ...amp import auto_cast

        @functools.wraps(fn)
        def wrapped(*a, **k):
            with auto_cast(enable=True, level=self.level,
                           dtype=self.get_attr("dtype", "bfloat16")):
                return fn(*a, **k)

        return wrapped


PassBase.register(_AmpPass)


@PassBase.register
class _Fp16Pass(_AmpPass):
    name = "auto_parallel_fp16"
    level = "O2"


class _IdentityPass(PassBase):
    """The XLA compiler performs this rewrite unconditionally."""

    def _apply_impl(self, fn):
        return fn


for _name in ("fuse_gemm_epilogue", "fused_attention", "fused_feedforward",
              "fuse_optimizer", "fuse_all_reduce", "fuse_elewise_add_act",
              "auto_parallel_sharding", "auto_parallel_gradient_merge"):
    PassBase.register(type(f"_{_name}_pass", (_IdentityPass,),
                          {"name": _name}))


def new_pass(name, pass_attrs=None):
    """ref: pass_base.py:131 new_pass."""
    pass_class = PassBase._REGISTERED_PASSES.get(name)
    if pass_class is None:
        raise ValueError(
            f"Pass {name!r} is not registered; available: "
            f"{sorted(PassBase._REGISTERED_PASSES)}"
        )
    pass_obj = pass_class()
    for k, v in (pass_attrs or {}).items():
        pass_obj.set_attr(k, v)
    return pass_obj


class PassManager:
    """ref: pass_base.py:350 — apply a pass list in order."""

    def __init__(self, passes, context=None, auto_solve_conflict=True):
        self._context = context or PassContext()
        self._passes = list(passes)

    def apply(self, fn):
        """Apply all passes to a step callable (the reference applies to
        [main_program]; a single callable is this runtime's program)."""
        if isinstance(fn, (list, tuple)):
            return type(fn)(self.apply(f) for f in fn)
        for p in self._passes:
            fn = p.apply(fn, self._context)
        return fn

    @property
    def context(self):
        return self._context

    @property
    def names(self):
        return [p.name for p in self._passes]
