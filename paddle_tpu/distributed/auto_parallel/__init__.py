"""Auto-parallel: DistTensor semantics over GSPMD.

ref: python/paddle/distributed/auto_parallel/ — api.py:132
(shard_tensor), :580 (reshard), :679 (shard_layer), :1343
(shard_optimizer), process_mesh.py (ProcessMesh), and the C++
DistTensor/placements stack (phi/core/distributed/auto_parallel/
dist_tensor.h:39, placement_types.h).

TPU-native collapse: the reference re-implements sharding propagation
(45 SPMD rule files), a 15-function reshard engine, and a generated
DistTensor branch in every kernel — all of which exist inside XLA as
GSPMD. Here:

- ``ProcessMesh``        → a named ``jax.sharding.Mesh`` (axis names =
  dim_names), with sub-mesh indexing.
- ``Shard(i)/Replicate/Partial`` placements → a ``PartitionSpec``.
- ``shard_tensor``       → device_put with the NamedSharding (eager) or
  with_sharding_constraint (traced): the tensor IS the dist tensor —
  no wrapper class, `.placements`/`.process_mesh` hang off the Tensor.
- ``reshard``            → placement change; XLA emits the collective
  (all-gather / reduce-scatter / all-to-all) the reshard engine would
  have picked.
- SPMD propagation       → GSPMD's solver at compile time.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...base.tensor import Tensor

__all__ = [
    "ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
    "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
    "shard_optimizer", "get_mesh", "set_mesh",
]


# ---------------------------------------------------------------------------
# placements (ref: placement_types.h — Shard/Replicate/Partial)
# ---------------------------------------------------------------------------


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Shard tensor dim ``dim`` across this mesh axis."""

    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    """Pending-reduction placement (ref: placement_types.h Partial).
    GSPMD materializes partial sums internally; a user-visible Partial
    is resolved to Replicate at the next reshard, so we track it for
    API parity and treat it as replicated for layout purposes."""

    def __init__(self, reduce_type=None):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type or 'sum'})"

    def __eq__(self, other):
        return isinstance(other, Partial)

    def __hash__(self):
        return hash("partial")


# ---------------------------------------------------------------------------
# ProcessMesh (ref: auto_parallel/process_mesh.py)
# ---------------------------------------------------------------------------

_global_mesh: Optional["ProcessMesh"] = None


def set_mesh(mesh: "ProcessMesh"):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional["ProcessMesh"]:
    return _global_mesh


class ProcessMesh:
    """N-D logical device mesh with named dims (ref: process_mesh.py).

    Backed by a jax Mesh over the visible devices in row-major process
    order; ``dim_names`` become the jax mesh axis names GSPMD shardings
    reference.
    """

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        if arr.dtype == object:
            raise ValueError("mesh must be an integer array of process ids")
        self._shape = list(arr.shape)
        self._process_ids = arr.flatten().tolist()
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(
                f"dim_names {dim_names} does not match mesh ndim {arr.ndim}"
            )
        self._dim_names = list(dim_names)
        devices = jax.devices()
        if max(self._process_ids) >= len(devices):
            raise ValueError(
                f"mesh references device {max(self._process_ids)} but only "
                f"{len(devices)} devices are visible (set "
                "--xla_force_host_platform_device_count for CPU testing)"
            )
        dev_arr = np.asarray([devices[i] for i in self._process_ids]).reshape(
            self._shape
        )
        self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))

    # -- reference API surface -----------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return list(self._process_ids)

    @property
    def mesh(self):
        return np.asarray(self._process_ids).reshape(self._shape)

    @property
    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    def get_dim_size(self, dim_name: str) -> int:
        return self._shape[self._dim_names.index(dim_name)]

    def get_mesh_with_dim(self, dim_name: str, index: Optional[int] = None):
        """Sub-mesh: move ``dim_name`` first, optionally index into it
        (ref: process_mesh.py get_mesh_with_dim)."""
        axis = self._dim_names.index(dim_name)
        arr = self.mesh
        moved = np.moveaxis(arr, axis, 0)
        names = [dim_name] + [n for n in self._dim_names if n != dim_name]
        if index is None:
            return ProcessMesh(moved, names)
        return ProcessMesh(moved[index], names[1:])

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self._shape == other._shape
            and self._process_ids == other._process_ids
            and self._dim_names == other._dim_names
        )

    def __repr__(self):
        return (
            f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names})"
        )


# ---------------------------------------------------------------------------
# placement <-> PartitionSpec
# ---------------------------------------------------------------------------


def _placements_to_spec(placements: Sequence[Placement], ndim: int,
                        mesh: ProcessMesh) -> PartitionSpec:
    """[per-mesh-axis placements] → PartitionSpec over tensor dims."""
    spec: List = [None] * ndim
    for mesh_axis, p in enumerate(placements):
        if isinstance(p, Shard):
            d = p.dim if p.dim >= 0 else p.dim + ndim
            if not 0 <= d < ndim:
                raise ValueError(f"Shard dim {p.dim} out of range for ndim {ndim}")
            name = mesh._dim_names[mesh_axis]
            if spec[d] is None:
                spec[d] = name
            elif isinstance(spec[d], tuple):
                spec[d] = spec[d] + (name,)
            else:
                spec[d] = (spec[d], name)
    return PartitionSpec(*spec)


def _sharding_for(mesh: ProcessMesh, placements, ndim: int) -> NamedSharding:
    placements = list(placements)
    while len(placements) < mesh.ndim:
        placements.append(Replicate())
    return NamedSharding(
        mesh.jax_mesh, _placements_to_spec(placements, ndim, mesh)
    )


def _annotate(t: Tensor, mesh: ProcessMesh, placements):
    t._dist_attr = {"mesh": mesh, "placements": list(placements)}
    return t


# ---------------------------------------------------------------------------
# public API (ref: auto_parallel/api.py)
# ---------------------------------------------------------------------------


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 place=None, stop_gradient=None):
    """Place ``data`` on the mesh with the given placements
    (ref: api.py:132). The result is a normal Tensor whose array carries
    the NamedSharding — every op on it becomes a GSPMD op."""
    from ... import to_tensor
    from ...base.tape import apply

    t = data if isinstance(data, Tensor) else to_tensor(data, dtype=dtype)
    sharding = _sharding_for(mesh, placements, len(t.shape))

    def f(a):
        # device_put / sharding-constraint are differentiable primitives,
        # so the tape edge to the source tensor is preserved
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sharding)
        return jax.device_put(a, sharding)

    out = apply(f, t, op_name="shard_tensor")
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    out.name = t.name
    return _annotate(out, mesh, placements)


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    """ref: api.py dtensor_from_fn — build then shard."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(dist_tensor, mesh: ProcessMesh, placements):
    """Change placements (ref: api.py:580). XLA picks the collective:
    s→r all-gather, p→r all-reduce, s→s all-to-all — the reference's
    15 ReshardFunctions collapse into this one device_put."""
    return shard_tensor(dist_tensor, mesh, placements)


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Shard every parameter of ``layer`` (ref: api.py:679).

    ``shard_fn(name, layer, mesh)`` may place parameters itself; the
    default replicates parameters onto the mesh (matching the
    reference's default)."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for p in sublayer.parameters(include_sublayers=False):
                sharded = shard_tensor(p, mesh, [Replicate()] * mesh.ndim)
                p._data = sharded._data
                _annotate(p, mesh, sharded.placements)

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh)
        )
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh)
        )
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """ref: api.py:1343 — install a placement hook so optimizer state
    inherits each parameter's sharding (or ``shard_fn(accum_name,
    param, accum)`` decides)."""

    def place_like_param(arr, param):
        src = param._data
        if hasattr(src, "sharding") and not isinstance(src, jax.core.Tracer):
            if isinstance(arr, jax.core.Tracer):
                return jax.lax.with_sharding_constraint(arr, src.sharding)
            if arr.shape == src.shape:
                return jax.device_put(arr, src.sharding)
        return arr

    # the optimizer's placement hook covers every lazy state creation —
    # accumulators AND multi-precision master weights (optimizer.py
    # _get_accum/_master_weight both route new state through it)
    def placement(arr, param, name):
        if shard_fn is not None:
            out = shard_fn(name, param, Tensor(arr, _internal=True))
            return out._data if isinstance(out, Tensor) else out
        return place_like_param(arr, param)

    optimizer._accum_placement_fn = placement
    return optimizer
