"""paddle.distributed.rpc — user-facing RPC between workers.

ref: python/paddle/distributed/rpc/rpc.py (init_rpc:73 bootstraps a
TCPStore at the master, exchanges WorkerInfos, starts a brpc agent;
rpc_sync:143 / rpc_async:183 serialize the callable and run it on the
remote worker; shutdown:276 barriers then stops the agent).

TPU-native redesign: the master runs the line-JSON TCPStoreServer
(distributed/store.py) for discovery and barriers; each worker runs a
small threaded socket server executing pickled (fn, args, kwargs)
requests — the role brpc plays in the reference. Python pickle is the
wire format, exactly like the reference's serialized-python payloads:
a TRUSTED-CLUSTER protocol; never expose the ports beyond the job.

The compute path stays single-controller JAX; rpc exists for the
host-side control plane (metrics aggregation, orchestration, parameter
server clients) the reference uses it for.
"""
from __future__ import annotations

import os
import pickle
import socket
import threading
import time
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, NamedTuple, Optional

from ..store import TCPKVStore, TCPStoreServer

__all__ = [
    "init_rpc", "shutdown", "rpc_async", "rpc_sync",
    "get_worker_info", "get_all_worker_infos", "get_current_worker_info",
    "WorkerInfo",
]

_DEFAULT_RPC_TIMEOUT = 30.0


class WorkerInfo(NamedTuple):
    name: str
    rank: int
    ip: str
    port: int


class _State:
    def __init__(self):
        self.server_sock: Optional[socket.socket] = None
        self.server_thread: Optional[threading.Thread] = None
        self.store: Optional[TCPKVStore] = None
        self.store_server: Optional[TCPStoreServer] = None
        self.self_info: Optional[WorkerInfo] = None
        self.workers: Dict[str, WorkerInfo] = {}
        self.world_size = 0
        self.stop = threading.Event()
        self.pool = ThreadPoolExecutor(max_workers=8)


_state: Optional[_State] = None


def _recv_msg(f):
    head = f.read(8)
    if len(head) < 8:
        raise EOFError
    n = int.from_bytes(head, "big")
    return pickle.loads(f.read(n))


def _send_msg(f, obj):
    payload = pickle.dumps(obj)
    f.write(len(payload).to_bytes(8, "big") + payload)
    f.flush()


def _serve_loop(st: _State):
    st.server_sock.settimeout(0.2)
    while not st.stop.is_set():
        try:
            conn, _ = st.server_sock.accept()
        except socket.timeout:
            continue
        except OSError:
            break

        def handle(c=conn):
            try:
                with c, c.makefile("rwb") as f:
                    fn, args, kwargs = _recv_msg(f)
                    try:
                        result = fn(*args, **(kwargs or {}))
                        _send_msg(f, ("ok", result))
                    except Exception as e:  # noqa: BLE001 — marshalled to caller
                        _send_msg(f, ("err", f"{e!r}\n{traceback.format_exc()}"))
            except (OSError, EOFError):
                pass

        threading.Thread(target=handle, daemon=True).start()
    st.server_sock.close()


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC agent and discover all peers (ref:
    rpc.py:73 — same env-var fallbacks)."""
    global _state
    if _state is not None:
        raise RuntimeError("init_rpc already called; call shutdown() first")
    rank = int(os.environ["PADDLE_TRAINER_ID"]) if rank is None else rank
    world_size = (
        int(os.environ["PADDLE_TRAINERS_NUM"]) if world_size is None else world_size
    )
    master_endpoint = master_endpoint or os.environ["PADDLE_MASTER_ENDPOINT"]
    master_addr, master_port = master_endpoint.rsplit(":", 1)

    st = _State()
    st.world_size = world_size
    try:
        if rank == 0:
            st.store_server = TCPStoreServer(host="0.0.0.0", port=int(master_port))
        st.store = TCPKVStore(master_addr, int(master_port))
        st.store.wait_alive()

        # exec server on an ephemeral port
        st.server_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        st.server_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        st.server_sock.bind(("0.0.0.0", 0))
        st.server_sock.listen(64)
        port = st.server_sock.getsockname()[1]
        ip = os.getenv("PADDLE_WORKER_IP", "127.0.0.1")
        st.self_info = WorkerInfo(name, rank, ip, port)
        st.server_thread = threading.Thread(
            target=_serve_loop, args=(st,), daemon=True
        )
        st.server_thread.start()

        # exchange WorkerInfos through the store
        # (ref: _exchange_all_service_infos; duplicate ranks rejected)
        key = f"rpc/worker/{rank}"
        # atomic claim — two workers racing on one rank must not both win
        if not st.store.set_if_absent(key, pickle.dumps(st.self_info).hex()):
            other: WorkerInfo = pickle.loads(bytes.fromhex(st.store.get(key)))
            raise RuntimeError(
                f"rpc rank {rank} already registered by worker "
                f"{other.name!r} at {other.ip}:{other.port}"
            )
        deadline = time.time() + _DEFAULT_RPC_TIMEOUT
        while True:
            # dump() = keys+values in ONE backend round trip (a keys()+
            # N get() poll would open O(world_size^2) TCP conns/sec)
            entries = {
                k: v for k, v, _ in st.store.dump("rpc/worker/")
            }
            # all(...) guards the claim-visible-before-value-lands window
            # on backends without hard links (store.set_if_absent)
            if len(entries) >= world_size and all(entries.values()):
                break
            if time.time() > deadline:
                raise TimeoutError(
                    f"only {len(entries)}/{world_size} rpc workers joined"
                )
            time.sleep(0.1)
        for k in sorted(entries):
            info: WorkerInfo = pickle.loads(bytes.fromhex(entries[k]))
            if info.name in st.workers:
                raise RuntimeError(
                    f"duplicate rpc worker name {info.name!r} (ranks "
                    f"{st.workers[info.name].rank} and {info.rank})"
                )
            st.workers[info.name] = info
    except BaseException:
        # failed bootstrap must not leak the exec socket, serve thread,
        # or (rank 0) the bound master store — a retry would EADDRINUSE
        st.stop.set()
        if st.server_sock is not None:
            try:
                st.server_sock.close()
            except OSError:
                pass
        if st.server_thread is not None:
            st.server_thread.join(1.0)
        if st.store_server is not None:
            st.store_server.stop()
        raise
    _state = st


def _require_state() -> _State:
    if _state is None:
        raise RuntimeError("rpc not initialized; call init_rpc first")
    return _state


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Run ``fn(*args, **kwargs)`` on worker ``to``; block for the
    result (ref: rpc.py:143). ``fn`` must be picklable (importable)."""
    st = _require_state()
    if to not in st.workers:
        raise ValueError(f"unknown rpc worker {to!r}; have {sorted(st.workers)}")
    info = st.workers[to]
    with socket.create_connection((info.ip, info.port), timeout=timeout) as conn:
        conn.settimeout(timeout)
        with conn.makefile("rwb") as f:
            _send_msg(f, (fn, tuple(args or ()), dict(kwargs or {})))
            status, payload = _recv_msg(f)
    if status != "ok":
        raise RuntimeError(f"rpc to {to!r} failed: {payload}")
    return payload


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT) -> Future:
    """Like rpc_sync but returns a Future (ref: rpc.py:183 returns a
    FutureWrapper; concurrent.futures.Future has the same .wait()/
    .result() surface via result())."""
    st = _require_state()
    fut = st.pool.submit(rpc_sync, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # reference API compat: fut.wait()
    return fut


def _barrier(st: _State, key: str):
    st.store.add(key, 1)
    deadline = time.time() + _DEFAULT_RPC_TIMEOUT
    while int(st.store.get(key) or 0) < st.world_size:
        if time.time() > deadline:
            raise TimeoutError(f"rpc barrier {key} timed out")
        time.sleep(0.05)


def shutdown():
    """Barrier all workers, then stop agent + master store (ref:
    rpc.py:276). Two-phase: after the shutdown barrier, every
    non-master worker posts an explicit exit ack and does no further
    store access; the master stops the store only once all acks are in
    — no fixed-sleep race against slow workers."""
    global _state
    st = _state
    if st is None:
        return
    _barrier(st, "rpc/shutdown")
    st.stop.set()
    if st.server_thread is not None:
        st.server_thread.join(2.0)
    st.pool.shutdown(wait=False)
    if st.store_server is not None:
        deadline = time.time() + _DEFAULT_RPC_TIMEOUT
        while int(st.store.get("rpc/exited") or 0) < st.world_size - 1:
            if time.time() > deadline:
                break  # stop anyway; stragglers already passed the barrier
            time.sleep(0.05)
        st.store_server.stop()
    else:
        st.store.add("rpc/exited", 1)  # final store access
    _state = None


def get_worker_info(name) -> WorkerInfo:
    """ref: rpc.py:307."""
    return _require_state().workers[name]


def get_all_worker_infos():
    """ref: rpc.py:337."""
    return sorted(_require_state().workers.values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    """ref: rpc.py:364."""
    return _require_state().self_info
